"""Monitor fan-out + flops profiler tests (reference monitor/monitor.py,
profiling/flops_profiler/profiler.py capability)."""

import os

import numpy as np
import pytest

import jax

import shuffle_exchange_tpu as sxt
from shuffle_exchange_tpu.models import Transformer, tiny
from shuffle_exchange_tpu.monitor import CSVMonitor, MonitorMaster
from shuffle_exchange_tpu.parallel import reset_topology
from shuffle_exchange_tpu.profiling import (compiled_flops, count_params,
                                            flops_to_string, get_model_profile,
                                            number_to_string, params_breakdown)


class _CSVCfg:
    enabled = True
    output_path = ""
    job_name = "job"


def test_csv_monitor_writes_per_label_files(tmp_path):
    cfg = _CSVCfg()
    cfg.output_path = str(tmp_path)
    mon = CSVMonitor(cfg)
    mon.write_events([("Train/loss", 1.5, 10), ("Train/lr", 0.1, 10)])
    mon.write_events([("Train/loss", 1.2, 20)])
    loss_file = tmp_path / "job" / "Train_loss.csv"
    assert loss_file.exists()
    lines = loss_file.read_text().strip().splitlines()
    assert lines[0] == "step,Train/loss" and len(lines) == 3
    assert lines[2].startswith("20,")


def test_formatting_helpers():
    assert number_to_string(1.5e12) == "1.50 T"
    assert number_to_string(2_000_000) == "2.00 M"
    assert flops_to_string(3e9) == "3.00 GFLOPS"


def test_count_and_breakdown():
    model = Transformer(tiny(vocab=64, d=32, layers=2, heads=4, seq=32))
    params = model.init(jax.random.PRNGKey(0))
    n = count_params(params)
    bd = params_breakdown(params, depth=1)
    assert n == sum(bd.values()) and bd["layers"] > 0 and n > 0


def test_get_model_profile():
    model = Transformer(tiny(vocab=64, d=32, layers=2, heads=4, seq=32))
    params = model.init(jax.random.PRNGKey(0))
    batch = {"input_ids": np.zeros((2, 16), np.int32)}
    flops, macs, n_params = get_model_profile(model=model, params=params, batch=batch)
    assert flops > 0 and macs == flops / 2 and n_params == count_params(params)
    s_flops, s_macs, s_params = get_model_profile(model=model, params=params, batch=batch,
                                                  as_string=True)
    assert "FLOPS" in s_flops


def test_engine_monitor_and_profiler_integration(tmp_path):
    reset_topology()
    model = Transformer(tiny(vocab=64, d=32, layers=2, heads=4, seq=32))
    prof_file = str(tmp_path / "prof.txt")
    engine, *_ = sxt.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 10**9,
        "csv_monitor": {"enabled": True, "output_path": str(tmp_path), "job_name": "t"},
        "flops_profiler": {"enabled": True, "profile_step": 2, "detailed": True,
                           "output_file": prof_file},
    })
    assert engine.monitor.enabled
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 64, size=(8, 32)).astype(np.int32)}
    for _ in range(2):
        loss = engine.train_batch(batch)
    assert np.isfinite(float(loss))
    csv_dir = tmp_path / "t"
    assert (csv_dir / "Train_Samples_train_loss.csv").exists()
    assert (csv_dir / "Train_Samples_lr.csv").exists()
    text = open(prof_file).read()
    assert "Flops Profiler" in text and "achieved:" in text and "params:" in text


def test_comet_monitor_section_and_graceful_disable(monkeypatch):
    """Reference monitor/comet.py parity: the comet section parses, and the
    sink disables itself with a warning when comet_ml import fails (forced
    here so the test stays deterministic if comet_ml ever gets installed)."""
    import sys

    from shuffle_exchange_tpu.config import SXConfig
    from shuffle_exchange_tpu.monitor.monitor import CometMonitor, MonitorMaster

    cfg = SXConfig.load({
        "train_batch_size": 8,
        "comet": {"enabled": True, "project": "p", "workspace": "w",
                  "experiment_name": "run1"},
    }, 1)
    assert cfg.comet.enabled and cfg.comet.project == "p"
    monkeypatch.setitem(sys.modules, "comet_ml", None)   # import -> ImportError
    mon = CometMonitor(cfg.comet)
    assert not mon.enabled
    master = MonitorMaster(cfg)
    assert master.comet_monitor is not None


def test_comet_per_metric_sample_gating(monkeypatch):
    """ADVICE r3: the Comet gate is per-metric by elapsed *samples* (the
    event step), mirroring the reference EventsLogScheduler — not every-Nth
    write_events call shared across metrics."""
    import sys
    import types

    from shuffle_exchange_tpu.config import SXConfig
    from shuffle_exchange_tpu.monitor.monitor import CometMonitor

    logged = []

    class _Exp:
        def set_name(self, n):
            pass

        def log_metric(self, label, value, step=None):
            logged.append((label, step))

    fake = types.ModuleType("comet_ml")
    fake.start = lambda **kw: _Exp()
    monkeypatch.setitem(sys.modules, "comet_ml", fake)

    cfg = SXConfig.load({
        "train_batch_size": 8,
        "comet": {"enabled": True, "samples_log_interval": 100},
    }, 1)
    mon = CometMonitor(cfg.comet)
    assert mon.enabled
    # global-samples steps 0,8,16,...: each call carries two metrics.
    for step in range(0, 250, 8):
        mon.write_events([("Train/loss", 1.0, step), ("Train/lr", 0.1, step)])
    loss_steps = [s for l, s in logged if l == "Train/loss"]
    lr_steps = [s for l, s in logged if l == "Train/lr"]
    # First point logs; next logs once >=100 samples elapsed per metric.
    assert loss_steps == [0, 104, 208]
    assert lr_steps == [0, 104, 208]
