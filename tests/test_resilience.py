"""Resilience layer: atomic checkpoints, crash→restart→bit-exact-resume,
non-finite policies, preemption hook, watchdog, retention GC, elastic agent.

Every crash here is INJECTED through the shuffle_exchange_tpu.testing.faults
seam at a real code site (shard write, manifest write, pre-commit,
pre-latest), and every recovery runs through the real engine/agent paths on
the 8-device virtual CPU mesh — no mocks of the save/load machinery itself.
"""

import os
import signal

import numpy as np
import pytest

import shuffle_exchange_tpu as sxt
from shuffle_exchange_tpu.parallel import reset_topology
from shuffle_exchange_tpu.testing import faults
from tests.test_engine import _batch, _toy_model


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()
    from shuffle_exchange_tpu.runtime.resilience import uninstall_preemption_hook

    uninstall_preemption_hook()


def _cfg(**extra):
    cfg = {"train_batch_size": 32, "steps_per_print": 10**9,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "checkpoint": {"writer": "fast"}}
    cfg.update(extra)
    return cfg


def _engine(**extra):
    reset_topology()
    engine, *_ = sxt.initialize(model=_toy_model(), config=_cfg(**extra))
    return engine


def _weights(engine):
    return np.asarray(engine.state.master["w1"])


# ---------------------------------------------------------------------------
# Tentpole: crash at any point during save → previous commit loadable,
# bit-exact resume, driven through the real ElasticAgent restart loop
# ---------------------------------------------------------------------------

CRASH_POINTS = [
    ("ckpt_shard_write", dict(index=0)),                  # first shard
    ("ckpt_shard_write", dict(index=2, byte_offset=16)),  # torn mid-file
    ("ckpt_manifest_write", dict()),                      # shards ok, manifest lost
    ("ckpt_item_save", dict(index=1)),                    # model done, opt never starts
    ("ckpt_pre_commit", dict()),                          # staged, never renamed
    ("ckpt_pre_latest", dict()),                          # committed, pointer stale
]


@pytest.mark.parametrize("site,kw", CRASH_POINTS,
                         ids=[f"{s}-{k.get('index', 0)}" for s, k in CRASH_POINTS])
def test_crash_during_save_resumes_bit_exact(tmp_path, site, kw):
    """A kill at any save site leaves a committed checkpoint; the
    ElasticAgent restart loop resumes from it and the final weights are
    bit-identical to a run that was never interrupted."""
    from shuffle_exchange_tpu.launcher import ElasticAgent

    ckpt = str(tmp_path / "ck")
    batch = _batch()

    # reference: 4 uninterrupted steps with a mid-run save
    ref = _engine()
    for _ in range(2):
        ref.train_batch(batch)
    ref.save_checkpoint(str(tmp_path / "ref"))
    for _ in range(2):
        ref.train_batch(batch)
    ref_w = _weights(ref)

    attempts = []

    def train_fn(restart_count):
        attempts.append(restart_count)
        engine = _engine()
        from shuffle_exchange_tpu.checkpoint import read_latest_tag

        if read_latest_tag(ckpt) is not None:
            engine.load_checkpoint(ckpt)
        while engine.global_steps < 4:
            engine.train_batch(batch)
            if engine.global_steps == 2 and len(attempts) == 1:
                engine.save_checkpoint(ckpt)          # commits step 2
                faults.arm(site, **kw)
                engine.train_batch(batch)
                engine.save_checkpoint(ckpt)          # killed by the fault
                raise AssertionError("injected fault did not fire")
        return engine

    agent = ElasticAgent(max_restarts=2, backoff_s=0.0)
    engine = agent.run(train_fn)
    assert attempts == [0, 1]              # exactly one injected crash
    assert engine.global_steps == 4
    np.testing.assert_array_equal(_weights(engine), ref_w)


def test_every_crash_point_leaves_previous_commit(tmp_path):
    """Direct (agent-free) check: after each injected save crash, the
    previous committed tag is what loads, bit-exactly."""
    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine()
    for _ in range(2):
        engine.train_batch(batch)
    engine.save_checkpoint(ckpt)
    committed_w = _weights(engine).copy()
    engine.train_batch(batch)
    faults.arm("ckpt_shard_write", index=1, byte_offset=4)
    with pytest.raises(faults.InjectedFault):
        engine.save_checkpoint(ckpt)

    fresh = _engine()
    path, _ = fresh.load_checkpoint(ckpt)
    assert path.endswith("global_step2")
    assert fresh.global_steps == 2
    np.testing.assert_array_equal(_weights(fresh), committed_w)


# ---------------------------------------------------------------------------
# Integrity verification + fallback (acceptance: corrupted shard rejected
# with leaf/file named; torn latest / missing manifest fall back, one warning)
# ---------------------------------------------------------------------------


def test_corrupted_shard_rejected_names_leaf_and_file(tmp_path):
    from shuffle_exchange_tpu.checkpoint import CheckpointCorruption, NativeCheckpointEngine

    ckpt = str(tmp_path / "ck")
    engine = _engine()
    engine.train_batch(_batch())
    engine.save_checkpoint(ckpt)
    faults.arm("corrupt_shard", index=0, byte_offset=2)
    faults.after_commit(os.path.join(ckpt, "global_step1"))

    eng = NativeCheckpointEngine()
    with pytest.raises(CheckpointCorruption) as ei:
        eng.load(os.path.join(ckpt, "global_step1", "model"),
                 target=engine.state.master)
    msg = str(ei.value)
    assert "checksum mismatch" in msg
    assert ".bin" in msg            # the file is named
    assert "leaf" in msg            # ... and the leaf


def test_corrupt_latest_tag_falls_back_with_one_warning(tmp_path, monkeypatch):
    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine()
    for step in range(2):
        engine.train_batch(batch)
        engine.save_checkpoint(ckpt)
    faults.arm("corrupt_shard", index=0, byte_offset=0)
    faults.after_commit(os.path.join(ckpt, "global_step2"))

    fresh = _engine()
    from shuffle_exchange_tpu.utils.logging import logger as sxt_logger

    warnings = []
    monkeypatch.setattr(sxt_logger, "warning",
                        lambda msg, *a, **k: warnings.append(str(msg)))
    path, _ = fresh.load_checkpoint(ckpt)
    assert path.endswith("global_step1")
    assert len([m for m in warnings if "falling back" in m]) == 1


def test_missing_manifest_falls_back(tmp_path):
    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine()
    for _ in range(2):
        engine.train_batch(batch)
        engine.save_checkpoint(ckpt)
    faults.arm("drop_manifest", index=0)
    faults.after_commit(os.path.join(ckpt, "global_step2"))

    fresh = _engine()
    path, _ = fresh.load_checkpoint(ckpt)
    assert path.endswith("global_step1")
    assert fresh.global_steps == 1


def test_torn_latest_falls_back_to_newest_complete(tmp_path):
    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine()
    for _ in range(2):
        engine.train_batch(batch)
        engine.save_checkpoint(ckpt)
    with open(os.path.join(ckpt, "latest"), "w") as f:
        f.write("   \n")

    fresh = _engine()
    path, _ = fresh.load_checkpoint(ckpt)
    assert path.endswith("global_step2")      # newest complete tag


def test_explicit_tag_never_falls_back(tmp_path):
    from shuffle_exchange_tpu.config import ConfigError

    ckpt = str(tmp_path / "ck")
    engine = _engine()
    engine.train_batch(_batch())
    engine.save_checkpoint(ckpt)
    with pytest.raises(ConfigError):
        engine.load_checkpoint(ckpt, tag="global_step999")


def test_serving_load_falls_back(tmp_path):
    """The serving path degrades the same way the trainer does."""
    from shuffle_exchange_tpu.inference import InferenceConfig, InferenceEngine
    from shuffle_exchange_tpu.models import Transformer, tiny

    reset_topology()
    model = Transformer(tiny(vocab=64, d=32, layers=2, heads=2, seq=32))
    engine, *_ = sxt.initialize(model=model, config={
        "train_batch_size": 8, "steps_per_print": 10**9,
        "checkpoint": {"writer": "fast"},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}})
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 64, size=(8, 32)).astype(np.int32)}
    for _ in range(2):
        engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path))
    faults.arm("corrupt_shard", index=0, byte_offset=1)
    faults.after_commit(os.path.join(str(tmp_path), "global_step2"))

    served = InferenceEngine.from_checkpoint(
        model, str(tmp_path), InferenceConfig(dtype="float32", max_seq_len=32))
    # fell back to step-1 weights; still serves
    prompts = np.random.default_rng(1).integers(0, 64, size=(2, 8)).astype(np.int32)
    out = served.generate(prompts, max_new_tokens=3)
    assert out.shape == (2, 3)
    # reload_weights keeps serving (returns False) when nothing is loadable
    assert served.reload_weights(str(tmp_path / "nonexistent")) is False


def test_v2_reload_guarded_by_live_sequences(tmp_path):
    """The paged engine refuses a hot weight swap while sequences hold KV
    computed under the current weights; flush() unblocks it."""
    from shuffle_exchange_tpu.inference import InferenceConfig
    from shuffle_exchange_tpu.inference.engine_v2 import InferenceEngineV2
    from shuffle_exchange_tpu.models import Transformer, tiny

    reset_topology()
    model = Transformer(tiny(vocab=64, d=32, layers=2, heads=2, seq=32))
    engine, *_ = sxt.initialize(model=model, config={
        "train_batch_size": 8, "steps_per_print": 10**9,
        "checkpoint": {"writer": "fast"},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}})
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 64, size=(8, 32)).astype(np.int32)}
    engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path))

    served = InferenceEngineV2.from_checkpoint(
        model, str(tmp_path),
        InferenceConfig(dtype="float32", max_seq_len=32,
                        kv_block_size=16, num_kv_blocks=12))
    served.put([1], [[3, 4, 5]])
    assert served.reload_weights(str(tmp_path)) is False       # live KV
    assert served.reload_weights(str(tmp_path), force=True) is True
    served.flush([1])
    assert served.reload_weights(str(tmp_path)) is True        # drained


# ---------------------------------------------------------------------------
# Retention GC
# ---------------------------------------------------------------------------


def test_keep_last_n_gc(tmp_path):
    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine(resilience={"keep_last_n": 2})
    for _ in range(4):
        engine.train_batch(batch)
        engine.save_checkpoint(ckpt)
    tags = sorted(n for n in os.listdir(ckpt) if n != "latest")
    assert tags == ["global_step3", "global_step4"]


def test_gc_never_deletes_latest_target(tmp_path):
    """Even when `latest` points at an old tag (e.g. after a rollback),
    GC keeps it."""
    from shuffle_exchange_tpu.checkpoint import write_latest_tag
    from shuffle_exchange_tpu.runtime.resilience import gc_checkpoints

    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine()
    for _ in range(4):
        engine.train_batch(batch)
        engine.save_checkpoint(ckpt)
    write_latest_tag(ckpt, "global_step1")   # pointer pinned to the oldest
    deleted = gc_checkpoints(ckpt, keep_last_n=1)
    assert "global_step1" not in deleted
    assert os.path.isdir(os.path.join(ckpt, "global_step1"))


def test_gc_sweeps_stale_staging_dirs(tmp_path):
    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine(resilience={"keep_last_n": 3})
    engine.train_batch(batch)
    engine.save_checkpoint(ckpt)
    engine.train_batch(batch)
    faults.arm("ckpt_pre_commit")
    with pytest.raises(faults.InjectedFault):
        engine.save_checkpoint(ckpt)
    assert any(".tmp-" in n for n in os.listdir(ckpt))   # crash leftover
    engine.train_batch(batch)
    engine.save_checkpoint(ckpt)                          # GC runs post-commit
    assert not any(".tmp-" in n for n in os.listdir(ckpt))


# ---------------------------------------------------------------------------
# Non-finite sentinel
# ---------------------------------------------------------------------------


def test_nonfinite_skip_drops_update_in_graph(tmp_path):
    batch = _batch()
    engine = _engine()          # default policy: skip
    for _ in range(2):
        engine.train_batch(batch)
    w = _weights(engine).copy()
    step = int(np.asarray(engine.state.step))
    faults.arm("nan_loss", index=engine.global_steps)
    loss = engine.train_batch(batch)
    assert not np.isfinite(float(loss))
    np.testing.assert_array_equal(_weights(engine), w)      # update dropped
    assert int(np.asarray(engine.state.step)) == step       # step not advanced
    # training continues clean afterwards
    assert np.isfinite(float(engine.train_batch(batch)))


def test_nonfinite_raise(tmp_path):
    from shuffle_exchange_tpu.runtime.resilience import NonFiniteLossError

    engine = _engine(resilience={"nonfinite_policy": "raise"})
    faults.arm("nan_loss", index=0)
    with pytest.raises(NonFiniteLossError):
        engine.train_batch(_batch())


def test_nonfinite_rollback_restores_last_commit(tmp_path):
    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine(resilience={"nonfinite_policy": "rollback"})
    for _ in range(2):
        engine.train_batch(batch)
    engine.save_checkpoint(ckpt)
    saved_w = _weights(engine).copy()
    engine.train_batch(batch)
    faults.arm("nan_loss", index=engine.global_steps)
    engine.train_batch(batch)
    assert engine.global_steps == 2                       # back at the commit
    np.testing.assert_array_equal(_weights(engine), saved_w)
    assert engine.resilience.rollbacks == 1
    assert engine.monitor.memory_monitor.latest("resilience/rollbacks") == 1


def test_nonfinite_rollback_without_checkpoint_raises():
    from shuffle_exchange_tpu.runtime.resilience import NonFiniteLossError

    engine = _engine(resilience={"nonfinite_policy": "rollback"})
    faults.arm("nan_loss", index=0)
    with pytest.raises(NonFiniteLossError, match="no checkpoint"):
        engine.train_batch(_batch())


def test_nonfinite_rollback_no_progress_raises(tmp_path):
    """A second non-finite step at the same global step (no progress since
    the rollback) must raise instead of looping forever."""
    from shuffle_exchange_tpu.runtime.resilience import NonFiniteLossError

    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine(resilience={"nonfinite_policy": "rollback"})
    engine.train_batch(batch)
    engine.save_checkpoint(ckpt)
    faults.arm("nan_loss", index=1)
    engine.train_batch(batch)                 # rollback #1 (back to step 1)
    faults.arm("nan_loss", index=1)
    with pytest.raises(NonFiniteLossError, match="no progress"):
        engine.train_batch(batch)


def test_fp16_overflow_is_not_treated_as_nonfinite(tmp_path):
    """A routine dynamic-loss-scale overflow has its own handling (skip +
    halve the scale); under rollback/raise policies it must NOT trigger a
    rollback or kill the worker."""
    batch = _batch()
    engine = _engine(fp16={"enabled": True, "initial_scale_power": 32},
                     resilience={"nonfinite_policy": "raise"})
    # 2^32 loss scale overflows the toy model's fp16 grads on step 1;
    # with the sentinel excluding overflow this is a plain skipped step
    engine.train_batch(batch)
    assert engine.skipped_steps >= 1
    # training proceeds, and the scale backs off (after the hysteresis
    # window) instead of the worker dying
    for _ in range(3):
        engine.train_batch(batch)
    assert engine.loss_scale() < 2.0 ** 32


def test_invalid_nonfinite_policy_rejected():
    from shuffle_exchange_tpu.config import ConfigError

    with pytest.raises(ConfigError, match="nonfinite_policy"):
        _engine(resilience={"nonfinite_policy": "explode"})


# ---------------------------------------------------------------------------
# Preemption hook + watchdog
# ---------------------------------------------------------------------------


def test_sigterm_mid_step_saves_and_exits(tmp_path):
    from shuffle_exchange_tpu.checkpoint import read_latest_tag

    ckpt = str(tmp_path / "ck")
    batch = _batch()
    engine = _engine()
    engine.train_batch(batch)
    engine.save_checkpoint(ckpt)        # arms the preemption hook at ckpt
    engine.train_batch(batch)
    faults.arm("sigterm_mid_step", index=engine.global_steps)
    with pytest.raises(SystemExit) as ei:
        engine.train_batch(batch)
    assert ei.value.code == 128 + signal.SIGTERM
    # the final synchronous save committed step 2 before exit
    assert read_latest_tag(ckpt) == "global_step2"
    assert engine.resilience.preemptions == 1

    fresh = _engine()
    fresh.load_checkpoint(ckpt)
    assert fresh.global_steps == 2


def test_preemption_save_disabled(tmp_path):
    engine = _engine(resilience={"preemption_save": False})
    engine.train_batch(_batch())
    engine.save_checkpoint(str(tmp_path / "ck"))
    from shuffle_exchange_tpu.runtime import resilience as res

    assert not res._PREEMPTION_INSTALLED


def test_watchdog_flags_hung_step():
    import time

    from shuffle_exchange_tpu.runtime.resilience import StepWatchdog

    fired = []
    wd = StepWatchdog(0.02, lambda step, t: fired.append((step, t)))
    wd.start(step=7)
    time.sleep(0.1)
    assert fired and fired[0][0] == 7
    wd.stop()
    # a fast step never fires
    fired.clear()
    wd.start(step=8)
    wd.stop()
    time.sleep(0.05)
    assert not fired


def test_watchdog_engine_counter(monkeypatch):
    """A hung step surfaces through the monitor counter."""
    import time

    batch = _batch()
    engine = _engine(resilience={"watchdog_timeout_s": 0.01})
    orig = engine._train_step

    def slow_step(*a, **k):
        time.sleep(0.1)
        return orig(*a, **k)

    monkeypatch.setattr(engine, "_train_step", slow_step)
    engine.train_batch(batch)
    assert engine.resilience.watchdog.hung_steps >= 1
    assert engine.monitor.memory_monitor.latest("resilience/hung_steps") >= 1


# ---------------------------------------------------------------------------
# ElasticAgent satellites
# ---------------------------------------------------------------------------


def test_elastic_agent_backoff_ceiling(monkeypatch):
    from shuffle_exchange_tpu.launcher import ElasticAgent

    delays = []
    monkeypatch.setattr("time.sleep", lambda s: delays.append(s))
    agent = ElasticAgent(max_restarts=6, backoff_s=1.0, max_backoff_s=5.0)
    n = [0]

    def fn(rc):
        n[0] += 1
        if n[0] <= 6:
            raise RuntimeError("boom")
        return "done"

    assert agent.run(fn) == "done"
    assert delays == [1.0, 2.0, 4.0, 5.0, 5.0, 5.0]   # capped at max_backoff_s


def test_elastic_agent_healthy_reset(monkeypatch):
    """An attempt that ran healthy for >= healthy_reset_s resets the budget:
    failures days apart never exhaust max_restarts."""
    from shuffle_exchange_tpu.launcher import ElasticAgent

    monkeypatch.setattr("time.sleep", lambda s: None)
    clock = [0.0]
    monkeypatch.setattr("time.monotonic", lambda: clock[0])
    agent = ElasticAgent(max_restarts=2, backoff_s=0.0, healthy_reset_s=100.0)
    n = [0]

    def fn(rc):
        n[0] += 1
        clock[0] += 1000.0      # every attempt runs "healthy" for 1000s
        if n[0] <= 5:
            raise RuntimeError("sporadic")
        return "done"

    assert agent.run(fn) == "done"          # 5 failures > max_restarts=2
    assert agent.total_restarts == 5
    assert agent.restart_count <= 2


def test_elastic_agent_emits_restart_events():
    from shuffle_exchange_tpu.launcher import ElasticAgent
    from shuffle_exchange_tpu.monitor import InMemoryMonitor

    mon = InMemoryMonitor()
    agent = ElasticAgent(max_restarts=3, backoff_s=0.0, monitor=mon)
    n = [0]

    def fn(rc):
        n[0] += 1
        if n[0] <= 2:
            raise RuntimeError("boom")
        return "ok"

    agent.run(fn)
    restarts = [e for e in mon.events if e[0] == "resilience/restarts"]
    assert [v for _, v, _ in restarts] == [1, 2]


# ---------------------------------------------------------------------------
# Engine-level satellites
# ---------------------------------------------------------------------------


def test_mock_engine_missing_path_is_file_not_found():
    from shuffle_exchange_tpu.checkpoint import MockCheckpointEngine

    eng = MockCheckpointEngine()
    with pytest.raises(FileNotFoundError):
        eng.load("/nope/never/saved")


def test_native_load_shape_mismatch_names_leaf(tmp_path):
    from shuffle_exchange_tpu.checkpoint import NativeCheckpointEngine

    import jax.numpy as jnp

    eng = NativeCheckpointEngine(blocking=True)
    state = {"w1": np.ones((4, 8), np.float32), "b1": np.zeros((8,), np.float32)}
    path = str(tmp_path / "item")
    eng.save(state, path)
    eng.commit("t")
    bad_target = {"w1": jnp.zeros((4, 8)), "b1": jnp.zeros((16,))}
    with pytest.raises(ValueError, match="b1"):
        eng.load(path, target=bad_target)


def test_ckpt_save_timing_counter(tmp_path):
    engine = _engine()
    engine.train_batch(_batch())
    engine.save_checkpoint(str(tmp_path / "ck"))
    v = engine.monitor.memory_monitor.latest("resilience/ckpt_save_s")
    assert v is not None and v >= 0.0
