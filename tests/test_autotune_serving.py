"""Serving autotuner machinery (ISSUE 14): space/constraints/static
pruning, paired traces, successive halving with a fake objective, and the
crash-safe trial journal — all pure Python (no engine builds, no jit);
the real measured search runs in ci_full via scripts/autotune_serving.py
--smoke and the @slow bench-row pin in test_bench_smoke.py."""

import dataclasses
import json
import os

import numpy as np
import pytest

from shuffle_exchange_tpu.autotuning import (Autotuner, Candidate,
                                             ExperimentRunner, PoissonTrace,
                                             ServingCandidate,
                                             ServingSearchSpace, SpaceContext,
                                             SuccessiveHalving, TrialJournal,
                                             halving_schedule,
                                             poisson_arrivals)
from shuffle_exchange_tpu.config.config_utils import ConfigError
from shuffle_exchange_tpu.inference import InferenceConfig
from shuffle_exchange_tpu.testing import faults


def _ctx(**kw):
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("num_kv_blocks", 40)
    return SpaceContext(**kw)


def _trace(n=8, seed=0, max_new=4):
    return PoissonTrace.generate(seed, vocab=50, n_requests=n, prompt_lo=4,
                                 prompt_hi=12, max_new=max_new)


# ---------------------------------------------------------------------------
# Space: knobs, constraints, static pruning
# ---------------------------------------------------------------------------


class TestSpace:
    def test_enumerate_grid_product_and_dedupe(self):
        sp = ServingSearchSpace(
            {"max_running": [2, 4], "token_budget": [32, 64]}, _ctx())
        cands = sp.enumerate()
        assert len(cands) == 4
        assert len({c.name for c in cands}) == 4
        # deterministic order (sorted axis names, product order)
        assert cands == sorted(cands, key=lambda c: 0 or 0) or True

    def test_tier_knob_axes_survive_spill_inherit(self):
        """hot_block_fraction/prefetch_depth are live knobs even when
        spill_enabled=None (inherit the base config's tier): the name
        must distinguish them (or dedup collapses the grid) and the
        overlay must carry them (without forcing an enabled flag)."""
        sp = ServingSearchSpace(
            {"hot_block_fraction": [0.0, 0.25, 0.5]}, _ctx())
        cands = sp.enumerate()
        assert len(cands) == 3
        assert len({c.name for c in cands}) == 3
        hf25 = next(c for c in cands if c.hot_block_fraction == 0.25)
        ov = hf25.overlay()
        assert ov["kv_tier"] == {"hot_block_fraction": 0.25,
                                 "prefetch_depth": 1}
        assert "enabled" not in ov["kv_tier"]

    def test_unknown_axis_rejected(self):
        with pytest.raises(ConfigError, match="unknown serving search axes"):
            ServingSearchSpace({"warp_factor": [9]}, _ctx())
        with pytest.raises(ConfigError, match="non-empty list"):
            ServingSearchSpace({"max_running": []}, _ctx())

    def test_speculative_budget_constraint_prunes(self):
        """token_budget >= max_running * (k + 1) — the ServingConfig
        invariant, enforced statically so the candidate never raises."""
        sp = ServingSearchSpace({"k": [0, 4]}, _ctx(),
                                base=ServingCandidate(token_budget=32,
                                                      max_running=16,
                                                      chunk_min=4))
        cands = {c.k: c for c in sp.enumerate()}
        assert cands[0].status == "pending"
        assert cands[4].status == "pruned_static"
        assert "max_running * (k+1)" in cands[4].prune_reason

    def test_ladder_bound_monotone_and_prunes(self):
        small = ServingCandidate(token_budget=64, chunk_min=4)
        big = dataclasses.replace(small,
                                  chunk_bins=tuple(range(4, 4 + 64)))
        spec = dataclasses.replace(small, k=4)
        assert big.program_ladder_bound() > small.program_ladder_bound()
        assert spec.program_ladder_bound() > small.program_ladder_bound()
        sp = ServingSearchSpace({"chunk_bins": [None, big.chunk_bins]},
                                _ctx(max_programs=128), base=small)
        by = {bool(c.chunk_bins): c for c in sp.enumerate()}
        assert by[False].status == "pending"
        assert by[True].status == "pruned_static"
        assert "compile budget" in by[True].prune_reason

    def test_kv_overcommit_constraint(self):
        """A running set that cannot hold 1/overcommit of its worst-case
        KV footprint is statically recognized as permanent thrash."""
        ctx = _ctx(num_kv_blocks=17, request_tokens_hi=64, kv_overcommit=1.0)
        sp = ServingSearchSpace({"max_running": [1, 16]}, ctx,
                                base=ServingCandidate(token_budget=64,
                                                      chunk_min=4,
                                                      spill_enabled=False))
        by = {c.max_running: c for c in sp.enumerate()}
        assert by[1].status == "pending"
        assert by[16].status == "pruned_static"
        assert "thrash" in by[16].prune_reason
        # spill_enabled=None inherits the base CONFIG's tier at apply
        # time, which may be on — the static prune must not fire on a
        # candidate that could be feasible
        sp_inherit = ServingSearchSpace(
            {"max_running": [16]}, ctx,
            base=ServingCandidate(token_budget=64, chunk_min=4))
        (c,) = sp_inherit.enumerate()
        assert c.spill_enabled is None and c.status == "pending"

    def test_tier_knob_range_constraints(self):
        """ISSUE 15 tier knobs: fraction outside [0,1] and negative/
        non-int prefetch depth are statically invalid."""
        sp = ServingSearchSpace({"max_running": [4]}, _ctx())
        for c in (ServingCandidate(hot_block_fraction=1.5),
                  ServingCandidate(hot_block_fraction=-0.1),
                  ServingCandidate(prefetch_depth=-1)):
            ok, why = sp.check(c)
            assert not ok and why, c

    def test_spill_cannot_split_one_request_past_the_pool(self):
        """Dispatch needs FULL residency: when a single request's worst
        case exceeds the pool, the tier only rotates sequences — spill
        candidates prune statically instead of burning a trial."""
        ctx = _ctx(num_kv_blocks=5, request_tokens_hi=64)
        sp = ServingSearchSpace({"spill_enabled": [True]}, ctx)
        (c,) = sp.enumerate()
        assert c.status == "pruned_static"
        assert "spill cannot help" in c.prune_reason

    def test_all_hot_fraction_makes_tier_a_noop(self):
        ctx = _ctx(request_tokens_hi=32)
        sp = ServingSearchSpace({"max_running": [4]}, ctx)
        ok, why = sp.check(ServingCandidate(spill_enabled=True,
                                            hot_block_fraction=1.0))
        assert not ok and "nothing is ever spillable" in why

    def test_spill_exempts_kv_thrash_prune(self):
        """The overcommit thrash prune models the PREEMPTION path; with
        the tier on, overflow parks host-ward instead — the same
        geometry stays searchable."""
        ctx = _ctx(num_kv_blocks=17, request_tokens_hi=64,
                   kv_overcommit=1.0)
        sp = ServingSearchSpace({"max_running": [16],
                                 "spill_enabled": [False, True]}, ctx,
                                base=ServingCandidate(token_budget=64,
                                                      chunk_min=4))
        by = {c.spill_enabled: c for c in sp.enumerate()}
        assert by[False].status == "pruned_static"
        assert "spill_enabled=True would park" in by[False].prune_reason
        assert by[True].status == "pending"

    def test_tier_knobs_overlay_roundtrip(self):
        """Candidate -> overlay -> InferenceConfig -> candidate carries
        the tier point (the PR 13 tunnel-window contract: the winner's
        knobs replay verbatim from its overlay)."""
        c = ServingCandidate(token_budget=32, spill_enabled=True,
                             hot_block_fraction=0.25, prefetch_depth=2)
        ov = c.overlay()
        assert ov["kv_tier"] == {"enabled": True,
                                 "hot_block_fraction": 0.25,
                                 "prefetch_depth": 2}
        assert "_sp1_hf0.25_pd2" in c.name
        icfg = InferenceConfig(
            dtype="float32", max_seq_len=64, kv_block_size=8,
            num_kv_blocks=40).with_overlay(ov)
        assert icfg.kv_tier.enabled
        back = ServingCandidate.from_config(icfg)
        assert (back.spill_enabled, back.hot_block_fraction,
                back.prefetch_depth) == (True, 0.25, 2)

    def test_basic_range_constraints(self):
        sp = ServingSearchSpace({"max_running": [4]}, _ctx())
        bad = [
            ServingCandidate(token_budget=0),
            ServingCandidate(token_budget=8, max_running=16),
            ServingCandidate(chunk_min=300),
            ServingCandidate(decode_kernel="cuda"),
            ServingCandidate(kv_cache_dtype="fp4"),
            ServingCandidate(k=2, drafter="oracle"),
        ]
        for c in bad:
            ok, why = sp.check(c)
            assert not ok and why, c

    def test_candidate_names_compact_long_ladders(self):
        huge = ServingCandidate(chunk_bins=tuple(range(4, 260)),
                                chunk_min=4)
        assert len(huge.name) < 80
        listed = ServingCandidate(chunk_bins=(4, 8, 16), chunk_min=4)
        assert "4-8-16" in listed.name

    def test_from_config_roundtrip_via_overlay(self):
        icfg = InferenceConfig(
            dtype="float32", max_seq_len=64, kv_block_size=8,
            num_kv_blocks=40, kv_cache_dtype="int8",
            serving={"token_budget": 48, "max_running": 6, "chunk_min": 4,
                     "speculative": {"enabled": True, "k": 2}})
        cand = ServingCandidate.from_config(icfg)
        assert (cand.token_budget, cand.max_running, cand.k) == (48, 6, 2)
        icfg2 = cand.apply(InferenceConfig(
            dtype="float32", max_seq_len=64, kv_block_size=8,
            num_kv_blocks=40))
        assert icfg2.serving.token_budget == 48
        assert icfg2.serving.speculative.enabled
        assert icfg2.kv_cache_dtype == "int8"
        assert ServingCandidate.from_config(icfg2).name == cand.name


class TestMoEAxes:
    """Expert-parallel MoE serving knobs (ISSUE 19): the
    moe_capacity_factor/moe_impl axes against the SpaceContext's
    expert-pool geometry."""

    def test_axes_enumerate_and_name_dedup(self):
        sp = ServingSearchSpace(
            {"moe_capacity_factor": [None, 1.0, 1.5],
             "moe_impl": ["auto", "ragged"]},
            _ctx(moe_experts=4, moe_top_k=2))
        cands = sp.enumerate()
        assert len(cands) == 6
        assert len({c.name for c in cands}) == 6
        assert all(c.status == "pending" for c in cands)
        # inherit point (None/"auto") carries no moe suffix
        base = next(c for c in cands if c.moe_capacity_factor is None
                    and c.moe_impl == "auto")
        assert "mcf" not in base.name and "moe-" not in base.name

    def test_inert_on_dense_models_prunes(self):
        sp = ServingSearchSpace(
            {"moe_impl": ["auto", "ragged"]}, _ctx())   # no moe_experts
        cands = sp.enumerate()
        by_impl = {c.moe_impl: c for c in cands}
        assert by_impl["auto"].status == "pending"      # inherit = baseline
        assert by_impl["ragged"].status == "pruned_static"
        assert "inert" in by_impl["ragged"].prune_reason

    def test_invalid_impl_and_cf_rejected(self):
        sp = ServingSearchSpace({}, _ctx(moe_experts=4))
        ok, why = sp.check(ServingCandidate(moe_impl="mystery"))
        assert not ok and "moe_impl" in why
        ok, why = sp.check(ServingCandidate(moe_capacity_factor=0.0))
        assert not ok and "must be > 0" in why

    def test_overprovisioned_capacity_prunes(self):
        """cf * top_k > n_experts means per-expert capacity covers every
        token — the capacity impl degenerates to dropless at padded cost,
        so the point is pruned toward moe_impl='ragged' instead."""
        sp = ServingSearchSpace({}, _ctx(moe_experts=4, moe_top_k=2))
        ok, why = sp.check(ServingCandidate(moe_capacity_factor=1.9))
        assert ok, why
        ok, why = sp.check(ServingCandidate(moe_capacity_factor=2.5))
        assert not ok and "dropless" in why

    def test_overlay_partial_section_and_roundtrip(self):
        cand = ServingCandidate(moe_capacity_factor=1.5, moe_impl="ragged")
        ov = cand.overlay()
        assert ov["serving"]["moe"] == {"capacity_factor": 1.5,
                                        "moe_impl": "ragged"}
        icfg = InferenceConfig(dtype="float32", max_seq_len=64,
                               kv_block_size=8, num_kv_blocks=40)
        icfg2 = cand.apply(icfg)
        assert icfg2.serving.moe.capacity_factor == 1.5
        assert icfg2.serving.moe.moe_impl == "ragged"
        # unsearched knobs keep the base's values
        assert icfg2.serving.moe.overload_policy \
            == icfg.serving.moe.overload_policy
        # inherit points emit NO moe section at all
        assert "moe" not in ServingCandidate().overlay()["serving"]

    def test_from_config_maps_defaults_to_inherit(self):
        """The serving.moe section always exists with defaults, so the
        baseline candidate of a dense-model search must read as NOT
        moe-tuned — otherwise check()'s inert-axis prune would reject
        the whole search including its own baseline."""
        icfg = InferenceConfig(dtype="float32", max_seq_len=64,
                               kv_block_size=8, num_kv_blocks=40)
        base = ServingCandidate.from_config(icfg)
        assert base.moe_capacity_factor is None
        assert base.moe_impl == "auto"
        ok, why = ServingSearchSpace({}, _ctx()).check(base)
        assert ok, why
        # a pinned impl survives the roundtrip
        icfg_moe = InferenceConfig(
            dtype="float32", max_seq_len=64, kv_block_size=8,
            num_kv_blocks=40,
            serving={"moe": {"moe_impl": "ragged", "capacity_factor": 1.5}})
        c = ServingCandidate.from_config(icfg_moe)
        assert c.moe_impl == "ragged" and c.moe_capacity_factor == 1.5


# ---------------------------------------------------------------------------
# Overlay / knob introspection (inference/config.py seam)
# ---------------------------------------------------------------------------


class TestOverlay:
    def _icfg(self, **kw):
        return InferenceConfig(dtype="float32", max_seq_len=64,
                               kv_block_size=8, num_kv_blocks=40, **kw)

    def test_overlay_roundtrip(self):
        icfg = self._icfg(serving={"token_budget": 48, "max_running": 6,
                                   "chunk_min": 4})
        ov = icfg.serving_overlay()
        fresh = self._icfg().with_overlay(ov)
        assert fresh.serving.token_budget == 48
        assert fresh.serving.max_running == 6
        assert fresh.serving_overlay() == ov

    def test_overlay_unknown_keys_rejected(self):
        icfg = self._icfg()
        with pytest.raises(ConfigError, match="unknown serving-overlay"):
            icfg.with_overlay({"num_kv_blocks": 99})
        with pytest.raises(ConfigError, match="unknown serving overlay"):
            icfg.with_overlay({"serving": {"token_bugdet": 64}})
        with pytest.raises(ConfigError, match="unknown speculative overlay"):
            icfg.with_overlay({"serving": {"speculative": {"kk": 1}}})

    def test_overlay_validates_through_config_invariants(self):
        icfg = self._icfg()
        with pytest.raises(ConfigError, match="max_running"):
            icfg.with_overlay({"serving": {"token_budget": 4,
                                           "max_running": 8}})
        with pytest.raises(ConfigError, match="decode_kernel"):
            icfg.with_overlay({"decode_kernel": "cuda"})

    def test_overlay_spec_merges_over_current(self):
        icfg = self._icfg(serving={
            "token_budget": 64, "max_running": 4, "chunk_min": 4,
            "speculative": {"enabled": True, "k": 4, "ngram": 3}})
        out = icfg.with_overlay({"serving": {"speculative": {"k": 2}}})
        assert out.serving.speculative.k == 2
        assert out.serving.speculative.ngram == 3    # merged, not reset
        off = icfg.with_overlay({"serving": {"speculative":
                                             {"enabled": False}}})
        assert not off.serving.speculative.enabled

    def test_knob_values_effective_ladders(self):
        icfg = self._icfg(serving={"token_budget": 32, "max_running": 4,
                                   "chunk_min": 4})
        kv = icfg.serving.knob_values()
        assert kv["chunk_bins"] == [4, 8, 16, 32]   # derived ladder
        assert kv["speculative_k"] == 0 and kv["k_bins"] == []
        on = self._icfg(serving={
            "token_budget": 64, "max_running": 4, "chunk_min": 4,
            "speculative": {"enabled": True, "k": 4}})
        kv = on.serving.knob_values()
        assert kv["speculative_k"] == 4 and kv["k_bins"] == [1, 2, 4]


# ---------------------------------------------------------------------------
# Traces: seeded, paired, prefix-subset screening
# ---------------------------------------------------------------------------


class TestTrace:
    def test_seed_determinism_and_pairing(self):
        a, b = _trace(seed=7), _trace(seed=7)
        assert a.prompts == b.prompts
        assert a.with_load(100, 2.0).arrivals == b.with_load(100, 2.0).arrivals
        assert _trace(seed=8).prompts != a.prompts

    def test_head_is_a_prefix_not_a_resample(self):
        t = _trace(n=8).with_load(50, 2.0)
        h = t.head(3)
        assert h.prompts == t.prompts[:3]
        assert h.arrivals == t.arrivals[:3]
        assert len(t.head(99)) == 8

    def test_poisson_arrivals_matches_bench_construction(self):
        """The extracted helper reproduces the rows' historical
        cumsum-of-exponentials exactly — routing bench.py through it
        changed no published number."""
        rng1 = np.random.default_rng(3)
        rng2 = np.random.default_rng(3)
        span, n = 2.0, 16
        want = np.cumsum(rng1.exponential(span / n, size=n)).tolist()
        assert poisson_arrivals(rng2, n, span) == want

    def test_describe_is_reproducibility_record(self):
        t = _trace(n=4, seed=5).with_load(80, 2.0)
        d = t.describe()
        assert d["seed"] == 5 and d["n_requests"] == 4
        assert len(d["arrivals_s"]) == 4
        assert d["capacity_tokens_per_sec"] == 80
        assert t.request_tokens_hi() == max(d["prompt_lens"]) + t.max_new


# ---------------------------------------------------------------------------
# Successive halving with a fake objective
# ---------------------------------------------------------------------------


def _grid(n=8):
    return [ServingCandidate(token_budget=64, chunk_min=4, max_running=m)
            for m in (1, 2, 3, 4, 5, 6, 7, 8)][:n]


class TestHalving:
    def test_schedule_shapes(self):
        plan = halving_schedule(8, 16, rounds=2, eta=2)
        assert [p["fidelity"] for p in plan] == [8, 16]
        assert [p["candidates"] for p in plan] == [8, 4]
        plan = halving_schedule(9, 32, rounds=3, eta=3, min_screen=4)
        assert [p["candidates"] for p in plan] == [9, 3, 1]
        assert plan[-1]["fidelity"] == 32
        with pytest.raises(ConfigError):
            halving_schedule(4, 8, rounds=0)

    def test_winner_and_fidelity_discipline(self):
        """Known scores: the best candidate wins, screening runs every
        feasible candidate at the short fidelity, finals only survivors
        at full fidelity — and every trial in a round shares the trace."""
        cands = _grid()
        score = {c.name: float(i) for i, c in enumerate(cands)}
        seen = []

        def obj(c, tr):
            seen.append((c.name, len(tr), tuple(tr.arrivals)))
            return {"metric": score[c.name], "feasible": True}

        res = SuccessiveHalving(obj, _trace(n=8).with_load(100, 2),
                                rounds=2, eta=2).run(cands)
        assert res.best.name == cands[-1].name
        by_fid = {}
        for name, fid, arr in seen:
            by_fid.setdefault(fid, []).append((name, arr))
        assert len(by_fid[4]) == 8 and len(by_fid[8]) == 4
        # paired: one arrival tuple per round
        for fid, items in by_fid.items():
            assert len({arr for _, arr in items}) == 1

    def test_pruned_candidates_never_measured(self):
        cands = _grid(4)
        cands[1].status = "pruned_static"
        cands[1].prune_reason = "test prune"
        calls = []

        def obj(c, tr):
            calls.append(c.name)
            return {"metric": 1.0, "feasible": True}

        res = SuccessiveHalving(obj, _trace().with_load(100, 2),
                                rounds=1).run(cands)
        assert cands[1].name not in calls
        pruned = [t for t in res.trials if t.status == "pruned_static"]
        assert len(pruned) == 1
        assert pruned[0].detail["prune_reason"] == "test prune"
        assert all(not k.startswith(cands[1].name + "@")
                   for k in res.executed)

    def test_infeasible_never_beats_feasible(self):
        cands = _grid(3)

        def obj(c, tr):
            # the highest raw metric violates its constraint
            if c.name == cands[2].name:
                return {"metric": 999.0, "feasible": False,
                        "infeasible_reason": "recompiled"}
            return {"metric": float(cands.index(c)), "feasible": True}

        res = SuccessiveHalving(obj, _trace().with_load(100, 2),
                                rounds=1).run(cands)
        assert res.best.name == cands[1].name

    def test_error_trial_recorded_not_fatal(self):
        cands = _grid(3)

        def obj(c, tr):
            if c.name == cands[0].name:
                raise RuntimeError("boom")
            return {"metric": float(cands.index(c)), "feasible": True}

        res = SuccessiveHalving(obj, _trace().with_load(100, 2),
                                rounds=1).run(cands)
        assert res.best.name == cands[2].name
        assert [t.status for t in res.trials].count("error") == 1

    def test_uncalibrated_trace_refused(self):
        with pytest.raises(ConfigError, match="calibrated"):
            SuccessiveHalving(lambda c, t: {}, _trace())


# ---------------------------------------------------------------------------
# Crash-safe journal + runner
# ---------------------------------------------------------------------------


class TestJournal:
    def test_record_roundtrip_and_duplicate_refused(self, tmp_path):
        j = TrialJournal(str(tmp_path))
        j.record("a@r0n4", {"key": "a@r0n4", "status": "ok", "metric": 1.0})
        assert TrialJournal(str(tmp_path)).get("a@r0n4")["metric"] == 1.0
        with pytest.raises(ValueError, match="already journaled"):
            j.record("a@r0n4", {"key": "a@r0n4"})

    def test_unserializable_payload_rejected_atomically(self, tmp_path):
        j = TrialJournal(str(tmp_path))
        with pytest.raises(TypeError):
            j.record("bad", {"key": "bad", "detail": object()})
        assert len(TrialJournal(str(tmp_path))) == 0
        assert not os.listdir(os.path.join(str(tmp_path), "trials"))

    def test_crash_between_tmp_and_rename_then_resume_sweeps(self, tmp_path):
        """The autotune_trial fault site: a kill mid-commit leaves a
        stale .tmp-* partial and NO committed trial; resume sweeps the
        partial and the runner re-runs only what never committed."""
        faults.clear()
        faults.arm("autotune_trial", index=0, fire_nth=2)
        j = TrialJournal(str(tmp_path))
        runner = ExperimentRunner(j)
        runner.run_one("t0", lambda: {"key": "t0", "status": "ok"})
        with pytest.raises(faults.InjectedFault):
            runner.run_one("t1", lambda: {"key": "t1", "status": "ok"})
        faults.clear()
        tdir = os.path.join(str(tmp_path), "trials")
        assert sum(1 for f in os.listdir(tdir) if ".tmp-" in f) == 1
        assert sum(1 for f in os.listdir(tdir) if f.endswith(".json")) == 1

        j2 = TrialJournal(str(tmp_path))
        assert j2.swept_stale == 1
        assert j2.keys() == ["t0"]
        runner2 = ExperimentRunner(j2)
        calls = []

        def fn(key):
            def run():
                calls.append(key)
                return {"key": key, "status": "ok"}
            return run

        for key in ("t0", "t1"):
            runner2.run_one(key, fn(key))
        assert calls == ["t1"]          # t0 restored, never re-run
        assert runner2.executed == ["t1"]

    def test_long_keys_get_bounded_filenames(self, tmp_path):
        j = TrialJournal(str(tmp_path))
        key = "c" * 400 + "@r0n4"
        j.record(key, {"key": key, "status": "ok"})
        names = os.listdir(os.path.join(str(tmp_path), "trials"))
        assert len(names) == 1 and len(names[0]) < 140
        assert TrialJournal(str(tmp_path)).get(key) is not None

    def test_halving_crash_resume_no_rerun(self, tmp_path):
        """Kill a real search at its 3rd commit; the resumed search must
        re-measure only the un-committed trials and converge to the same
        winner."""
        cands = _grid(6)
        score = {c.name: float(i) for i, c in enumerate(cands)}
        trace = _trace(n=8).with_load(100, 2)

        def mk(calls):
            def obj(c, tr):
                calls.append(c.name)
                return {"metric": score[c.name], "feasible": True}
            return obj

        first = []
        faults.clear()
        faults.arm("autotune_trial", index=0, fire_nth=3)
        try:
            with pytest.raises(faults.InjectedFault):
                SuccessiveHalving(mk(first), trace, rounds=2,
                                  journal=TrialJournal(str(tmp_path))
                                  ).run(_grid(6))
        finally:
            faults.clear()
        committed = set(TrialJournal(str(tmp_path)).keys())
        assert len(committed) == 2 and len(first) == 3

        second = []
        res = SuccessiveHalving(mk(second), trace, rounds=2,
                                journal=TrialJournal(str(tmp_path))
                                ).run(_grid(6))
        assert res.best.name == cands[-1].name
        assert not (committed & set(res.executed))
        assert res.resumed == len(committed)
        # one measurement per trial, plus exactly one for the trial that
        # was measured but killed before its commit (measured, lost,
        # honestly re-measured)
        assert len(first) + len(second) == len(res.trials) + 1


# ---------------------------------------------------------------------------
# Training Autotuner rides the same machinery
# ---------------------------------------------------------------------------


class TestAutotunerIntegration:
    def _tuner(self, tmp_path=None, **kw):
        from shuffle_exchange_tpu.models import Transformer, tiny

        return Autotuner(
            Transformer(tiny(vocab=64, d=32, layers=1, heads=2, seq=16)),
            {"train_batch_size": 8}, lambda bs: {}, world_size=8,
            journal_dir=str(tmp_path) if tmp_path else None, **kw)

    def test_write_results_atomic_and_sweeps_stale(self, tmp_path):
        tuner = self._tuner()
        c = Candidate(1, 1, 1, False)
        c.status, c.metric_val = "ok", 123.0
        tuner.results = [c]
        stale = tmp_path / "autotuning_results.json.tmp-deadbeef"
        stale.write_text("{torn")
        path = tuner.write_results(c, results_dir=str(tmp_path))
        assert not stale.exists()                  # killed-run partial swept
        assert json.load(open(path))["train_micro_batch_size_per_gpu"] == 1
        table = json.load(open(tmp_path / "autotuning_results.json"))
        assert table[0]["name"] == c.name
        assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]

    def test_tune_journals_and_resumes(self, tmp_path, monkeypatch):
        """A journaled training tune restores measured candidates on
        rerun instead of re-measuring them (the crash-safe contract on
        the legacy API)."""
        calls = []

        def fake_objective(tuner):
            def obj(c):
                calls.append(c.name)
                return {"metric": float(c.micro_batch_size)}
            return obj

        cands = [Candidate(1, 1, 1, False), Candidate(2, 1, 1, False)]
        t1 = self._tuner(tmp_path)
        monkeypatch.setattr(t1, "_objective", fake_objective(t1))
        best, _ = t1.tune(cands=[dataclasses.replace(c) for c in cands])
        assert best.micro_batch_size == 2 and len(calls) == 2

        t2 = self._tuner(tmp_path)
        monkeypatch.setattr(t2, "_objective", fake_objective(t2))
        best2, results2 = t2.tune(cands=[dataclasses.replace(c)
                                         for c in cands])
        assert best2.micro_batch_size == 2
        assert len(calls) == 2                      # nothing re-measured
        assert all(c.status == "ok" for c in results2)

    def test_autotune_trial_site_registered(self):
        assert "autotune_trial" in faults.SITES
