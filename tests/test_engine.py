"""End-to-end engine tests on the 8-device virtual CPU mesh.

Covers the reference's core train loop semantics (SURVEY.md §3.2): initialize
→ train_batch (fused) and forward/backward/step (staged), ZeRO stages as
sharding, fp16 dynamic loss scale, and the fork's decentralized sync methods.
"""

import numpy as np
import pytest

import shuffle_exchange_tpu as sxt


def _toy_model(din=8, dh=32, dout=4):
    import jax
    import jax.numpy as jnp

    class Toy:
        def init(self, rng):
            k1, k2 = jax.random.split(rng)
            return {
                "w1": jax.random.normal(k1, (din, dh)) * 0.1,
                "b1": jnp.zeros((dh,)),
                "w2": jax.random.normal(k2, (dh, dout)) * 0.1,
                "b2": jnp.zeros((dout,)),
            }

        def loss(self, params, batch, rng=None):
            x, y = batch["x"], batch["y"]
            h = jnp.tanh(x @ params["w1"].astype(x.dtype) + params["b1"].astype(x.dtype))
            logits = h @ params["w2"].astype(x.dtype) + params["b2"].astype(x.dtype)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    return Toy()


def _batch(n=32, din=8, dout=4, seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.normal(size=(n, din)).astype(np.float32),
            "y": rng.integers(0, dout, size=(n,)).astype(np.int32)}


def _make_engine(config_extra=None, **init_kwargs):
    cfg = {"train_batch_size": 32, "steps_per_print": 1000,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}}
    cfg.update(config_extra or {})
    engine, opt, loader, sched = sxt.initialize(model=_toy_model(), config=cfg, **init_kwargs)
    return engine


@pytest.mark.parametrize("stage", [0, 1, 3])
def test_train_batch_loss_decreases(stage):
    engine = _make_engine({"zero_optimization": {"stage": stage}, "bf16": {"enabled": True}})
    batch = _batch()
    losses = [float(engine.train_batch(batch)) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.9, losses
    assert engine.global_steps == 20


def test_gradient_accumulation_matches_big_batch():
    # gas=4 over the same data should follow a similar trajectory to gas=1.
    e1 = _make_engine({"gradient_accumulation_steps": 1})
    e2 = _make_engine({"gradient_accumulation_steps": 4})
    batch = _batch(32)
    l1 = [float(e1.train_batch(batch)) for _ in range(5)]
    l2 = [float(e2.train_batch(batch)) for _ in range(5)]
    np.testing.assert_allclose(l1[0], l2[0], rtol=1e-4)
    assert abs(l1[-1] - l2[-1]) < 0.2


def test_forward_backward_step_parity():
    engine = _make_engine()
    batch = _batch()
    loss0 = engine.forward(batch)
    engine.backward(loss0)
    engine.step()
    loss1 = engine.forward(batch)
    assert float(loss1) < float(loss0)


def test_fp16_dynamic_loss_scale_overflow_skip():
    engine = _make_engine({"fp16": {"enabled": True, "initial_scale_power": 4}})
    scale0 = engine.loss_scale()
    assert scale0 == 16.0
    batch = _batch()
    # poison one batch to overflow
    bad = dict(batch)
    bad["x"] = np.full_like(batch["x"], np.nan)  # NaN grads = guaranteed overflow signal
    # default hysteresis=2: the first overflow only consumes hysteresis
    # (reference DynamicLossScaler), the second consecutive one halves.
    engine.train_batch(bad)
    assert engine.loss_scale() == 16.0
    engine.train_batch(bad)
    assert engine.loss_scale() == 8.0
    # params were not corrupted by the skipped steps: clean training resumes
    losses = [float(engine.train_batch(batch)) for _ in range(5)]
    assert np.isfinite(losses).all()


def test_client_optimizer_and_scheduler():
    import optax

    engine, opt, _, sched = sxt.initialize(
        model=_toy_model(),
        config={"train_batch_size": 32},
        optimizer=optax.sgd(1e-2),
        lr_scheduler=lambda step: 1e-2,
    )
    batch = _batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


@pytest.mark.parametrize("method", ["RR", "shuffle", "H-RR", "Gossip"])
def test_decentralized_methods_train(method):
    engine = _make_engine(
        {"bf16": {"enabled": True}},
        method=method, rings=2, shuffle_step=3, slice_count=2,
    )
    assert engine.ensemble and engine.replicas == 4  # 8 devices / slice_count 2
    batch = _batch(32)
    losses = [float(engine.train_batch(batch)) for _ in range(12)]
    assert losses[-1] < losses[0], losses
    # control surface
    engine.shuffle_exchange()
    engine.reset_rings(4)
    engine.synchronization()
    # after synchronization all replicas should hold identical masters
    import jax

    m = jax.device_get(engine.state.master["w1"])
    for r in range(1, engine.replicas):
        np.testing.assert_allclose(m[0], m[r], rtol=1e-5)


def test_shuffle_rings_rerandomize():
    engine = _make_engine({}, method="shuffle", rings=2, shuffle_step=2, slice_count=1)
    a0 = engine.sync.ring_assignment.copy()
    batch = _batch()
    for _ in range(6):
        engine.train_batch(batch)
    assert engine.sync.batch_count == 6
    # shuffle_step=2 → 3 re-randomizations of 8 replicas into 2 rings; with
    # the deterministic seeded rng the assignment must have changed.
    assert not np.array_equal(a0, engine.sync.ring_assignment)


def test_gossip_state_pure_reads():
    """eval/forward must not advance the gossip protocol (alpha, pending)."""
    engine = _make_engine({}, method="Gossip", slice_count=2)
    batch = _batch()
    engine.train_batch(batch)
    alpha0 = engine.sync.alpha.copy()
    pending0 = list(engine.sync._pending)
    engine.eval_batch(batch)
    engine.forward(batch)
    engine.module_weights(consensus=False)
    np.testing.assert_array_equal(alpha0, engine.sync.alpha)
    assert pending0 == engine.sync._pending
    # grad-norm introspection API returns a real value after train_batch
    assert engine.get_global_grad_norm() is not None and np.isfinite(engine.get_global_grad_norm())


def test_ensemble_with_zero_stage_shards():
    """Decentralized sync composes with ZeRO stages (review regression)."""
    engine = _make_engine({"zero_optimization": {"stage": 1}, "bf16": {"enabled": True}},
                          method="RR", slice_count=2)
    batch = _batch()
    l0 = float(engine.train_batch(batch))
    l1 = float(engine.train_batch(batch))
    assert np.isfinite(l0) and np.isfinite(l1)


def test_decentralized_consensus_matches_sgd():
    """With method=RR and SGD, the consensus trajectory equals plain data-
    parallel SGD over the same global batch (gradient averaging at the
    consensus point; masters receive identical updates under linear SGD)."""
    import optax

    batch = _batch(32)
    e_ref, *_ = sxt.initialize(model=_toy_model(), config={"train_batch_size": 32},
                               optimizer=optax.sgd(1e-2))
    e_rr, *_ = sxt.initialize(model=_toy_model(), config={"train_batch_size": 32},
                              optimizer=optax.sgd(1e-2), method="RR", slice_count=2)
    for _ in range(5):
        l_ref = float(e_ref.train_batch(batch))
        l_rr = float(e_rr.train_batch(batch))
    np.testing.assert_allclose(l_ref, l_rr, rtol=2e-3)


def test_engine_compile_aot_warmup(devices8):
    """engine.compile(batch) pre-compiles the fused step (reference
    engine.compile(), runtime/engine.py:3970) without advancing RNG or
    counters — the subsequent trajectory is identical to not calling it."""
    import shuffle_exchange_tpu as sxt
    from shuffle_exchange_tpu.parallel import reset_topology

    def build():
        reset_topology()
        e, *_ = sxt.initialize(model=_toy_model(), config={
            "train_batch_size": 32,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "steps_per_print": 10**9})
        return e

    batch = _batch()
    e1, e2 = build(), build()
    e1.compile(batch)
    assert e1.global_steps == 0
    l1 = [float(e1.train_batch(batch)) for _ in range(2)]
    l2 = [float(e2.train_batch(batch)) for _ in range(2)]
    assert l1 == l2


def test_zero_init_and_gathered_parameters_api(devices8):
    """Reference-shaped zero.Init / GatheredParameters / no_sync code runs
    unchanged (the capabilities are structural here; the API shims keep
    user code source-compatible)."""
    import shuffle_exchange_tpu as sxt
    from shuffle_exchange_tpu.parallel import reset_topology

    reset_topology()
    with sxt.zero.Init(config_dict_or_path={"zero_optimization": {"stage": 3}}):
        model = _toy_model()
    engine, *_ = sxt.initialize(model=model, config={
        "train_batch_size": 32,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3},
        "steps_per_print": 10**9})
    with engine.no_sync():
        loss = engine.train_batch(_batch())
    assert np.isfinite(float(loss))
    with sxt.zero.GatheredParameters(engine.module_weights()) as w:
        leaf = np.asarray(next(iter(jax_leaves(w))))
        assert np.isfinite(leaf).all()


def jax_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def test_memory_breakdown_logs_and_config_fingerprint():
    """memory_breakdown was parse-only (same silent-flag class as
    sparse_gradients): steps_per_print now emits HBM stats. The config
    fingerprint is stable across engines with identical configs and
    differs when the config differs (cross-host consistency guard)."""
    import logging

    import shuffle_exchange_tpu as sxt
    from shuffle_exchange_tpu.models import Transformer, tiny
    from shuffle_exchange_tpu.parallel import reset_topology

    def build(**extra):
        reset_topology()
        cfg = {"train_batch_size": 8,
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
               "steps_per_print": 1, **extra}
        e, *_ = sxt.initialize(
            model=Transformer(tiny(vocab=64, d=32, layers=1, heads=2, seq=32)),
            config=cfg)
        return e

    engine = build(memory_breakdown=True)
    batch = {"input_ids": np.zeros((8, 32), np.int32)}
    from shuffle_exchange_tpu.utils.logging import logger as sxt_logger

    records = []

    class _Catch(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    h = _Catch()
    old_level = sxt_logger.level
    sxt_logger.addHandler(h)
    sxt_logger.setLevel(logging.INFO)
    try:
        engine.train_batch(batch)
    finally:
        sxt_logger.removeHandler(h)
        sxt_logger.setLevel(old_level)
    assert any("mem" in m for m in records), records[-5:]

    fp1 = engine._config_fingerprint()
    engine2 = build(memory_breakdown=True)
    assert engine2._config_fingerprint() == fp1
    engine3 = build(memory_breakdown=True, gradient_clipping=1.0)
    assert engine3._config_fingerprint() != fp1


def test_checkpoint_recovery_breadcrumb(tmp_path):
    """Reference engine.py writes a recovery script into checkpoints; the
    analog recovery.json carries the resume coordinates."""
    import json

    import shuffle_exchange_tpu as sxt
    from shuffle_exchange_tpu.models import Transformer, tiny
    from shuffle_exchange_tpu.parallel import reset_topology

    reset_topology()
    engine, *_ = sxt.initialize(
        model=Transformer(tiny(vocab=64, d=32, layers=1, heads=2, seq=32)),
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 10**9})
    engine.train_batch({"input_ids": np.zeros((8, 32), np.int32)})
    path = engine.save_checkpoint(str(tmp_path))
    rec = json.load(open(f"{path}/recovery.json"))
    assert rec["tag"] == "global_step1" and rec["global_steps"] == 1
    assert rec["mesh"]["data"] >= 1
    assert rec["config_fingerprint"] == engine._config_fingerprint().hex()
