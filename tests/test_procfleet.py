"""Cross-process fleet (ISSUE 17): the RPC replica boundary must keep
every ISSUE 12 robustness bar — zero lost requests, token parity,
typed failures — when the replica is a real process that really dies.

Tier-1 discipline: the unmarked tests are fake-clock health-machine and
wire-record tests (no engine, no sleeps, no processes). Everything that
spawns worker processes — the parity smoke, the real kill -9/SIGSTOP
drills, the drain-mid-death regression, the KV handoff — is @slow
(ci_full), because each worker is a fresh Python + jax process.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from shuffle_exchange_tpu.config import ConfigError
from shuffle_exchange_tpu.inference import (InferenceConfig, KVBlockPayload,
                                            ServingRequest)
from shuffle_exchange_tpu.serving.health import (H_ACTIVE, H_DEAD,
                                                 H_SUSPECT, HealthMonitor)
from shuffle_exchange_tpu.serving.rpc import RpcConnectionLost, RpcTimeout
from shuffle_exchange_tpu.serving.worker import (kv_payload_from_wire,
                                                 kv_payload_to_wire)

# ---------------------------------------------------------------------------
# RPC outcome observations on the health machine (fake clock, no sleeps)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _rcfg(**kw):
    base = dict(heartbeat_interval_s=1.0, suspect_after_misses=2,
                dead_after_misses=4, tick_timeout_s=10.0,
                health_check_interval_s=0.01)
    base.update(kw)
    return InferenceConfig(router=base).router


class TestRpcHealthObservations:
    """Satellite 2: crash-vs-hang discrimination. A SIGSTOPped worker is
    REACHABLE-hung (timeouts -> SUSPECT, the clock-run miss budget
    decides DEAD with the engine reachable); a kill -9'd worker is LOST
    (connection refused -> immediate DEAD, engine unreachable)."""

    def test_rpc_hung_suspects_then_miss_budget_kills_reachable(self):
        clock = FakeClock()
        hm = HealthMonitor(_rcfg(), clock=clock)
        hm.register(0)
        hm.rpc_ok(0)
        assert hm.rpc_hung(0, "rpc timeout") == H_SUSPECT
        assert hm.states() == {0: H_SUSPECT}
        # SUSPECT is not DEAD: the budget has not elapsed yet
        assert hm.check(lambda rid: True) == []
        # the process is alive the whole time — only the CLOCK kills it
        clock.t += 4.5
        dead = hm.check(lambda rid: True)
        assert [(d[0], d[2]) for d in dead] == [(0, True)]   # REACHABLE
        assert hm.states() == {0: H_DEAD}

    def test_rpc_ok_hysteresis_recovers_suspect(self):
        clock = FakeClock()
        hm = HealthMonitor(_rcfg(), clock=clock)
        hm.register(0)
        hm.rpc_ok(0)
        hm.rpc_hung(0, "one slow call (mid-compile)")
        assert hm.states() == {0: H_SUSPECT}
        hm.rpc_ok(0)   # the next successful exchange recovers it
        assert hm.states() == {0: H_ACTIVE}
        # and the beat was refreshed: no stale-clock kill afterwards
        clock.t += 3.0
        assert hm.check(lambda rid: True) == []

    def test_rpc_unreachable_is_immediate_dead_engine_lost(self):
        clock = FakeClock()
        hm = HealthMonitor(_rcfg(), clock=clock)
        hm.register(0)
        hm.rpc_ok(0)
        hm.rpc_unreachable(0, "connection refused")
        assert hm.states() == {0: H_DEAD}
        snap = hm.snapshot()[0]
        assert snap["engine_reachable"] is False
        # DEAD is terminal: later successes do not resurrect
        hm.rpc_ok(0)
        assert hm.states() == {0: H_DEAD}

    def test_hung_worker_repeated_timeouts_do_not_double_count(self):
        clock = FakeClock()
        hm = HealthMonitor(_rcfg(), clock=clock)
        hm.register(0)
        hm.rpc_ok(0)
        for _ in range(10):   # a frozen worker times out on EVERY call
            hm.rpc_hung(0, "rpc timeout")
        # still only SUSPECT: the miss budget, not the call count, kills
        assert hm.states() == {0: H_SUSPECT}
        clock.t += 2.0   # under the 4-miss budget
        assert hm.check(lambda rid: True) == []
        assert hm.states() == {0: H_SUSPECT}


class TestFleetModeConfig:
    def test_fleet_mode_validated(self):
        assert InferenceConfig(
            router={"fleet_mode": "process"}).router.fleet_mode == "process"
        with pytest.raises(ConfigError):
            InferenceConfig(router={"fleet_mode": "ray"})

    def test_rpc_knobs_validated(self):
        with pytest.raises(ConfigError):
            InferenceConfig(router={"rpc_call_timeout_s": 0.0})
        with pytest.raises(ConfigError):
            InferenceConfig(router={"rpc_connect_retries": -1})
        r = InferenceConfig(router={"rpc_call_timeout_s": 2.0,
                                    "rpc_ping_timeout_s": 0.5}).router
        assert r.rpc_call_timeout_s == 2.0


class TestFleetMetrics:
    """publish_metrics -> FleetMonitor plumbing, no processes: a
    duck-typed fleet (real counters, fake RpcClient handles) writes the
    ISSUE 17 rpc/* group the same fleet-scoped way the threaded router
    writes failover/* (latest value wins in aggregate())."""

    def _fleet(self):
        from shuffle_exchange_tpu.serving.procfleet import \
            ProcessReplicaRouter

        class _Client:
            def __init__(self):
                self.calls, self.timeouts, self.reconnects = 7, 2, 1

        class _Handle:
            def __init__(self):
                self.client = _Client()
                self.state = "active"

        fleet = object.__new__(ProcessReplicaRouter)
        fleet.workers = {0: _Handle(), 1: _Handle()}
        fleet.failovers, fleet.recovered = 1, 3
        fleet.reprefill_tokens, fleet.shed = 11, 0
        fleet._metrics_step = 0
        return fleet

    def test_publish_metrics_lands_in_fleet_monitor(self):
        from shuffle_exchange_tpu.monitor import FleetMonitor

        fm = FleetMonitor()
        fleet = self._fleet()
        vals = fleet.publish_metrics(fm)
        assert vals["rpc/calls"] == 14 and vals["rpc/timeouts"] == 4
        assert vals["rpc/workers_active"] == 2
        agg = fm.aggregate()
        assert agg["rpc"] == {"calls": 14, "timeouts": 4, "reconnects": 2,
                              "workers_active": 2}
        assert agg["failover"]["deaths"] == 1
        assert agg["failover"]["recovered_requests"] == 3

    def test_publish_forwards_rpc_group_downstream(self):
        from shuffle_exchange_tpu.monitor import FleetMonitor

        class _Sink:
            def __init__(self):
                self.events = []

            def write_events(self, evs):
                self.events.extend(evs)

        sink = _Sink()
        fm = FleetMonitor(downstream=sink)
        fleet = self._fleet()
        fleet.publish_metrics(fm)
        fleet.workers[0].client.calls = 9  # counters are cumulative
        fleet.publish_metrics(fm)
        assert fleet._metrics_step == 2
        fm.publish()
        labels = {lbl: v for lbl, v, _ in sink.events}
        assert labels["fleet/rpc/calls"] == 16  # latest write wins
        assert labels["fleet/rpc/timeouts"] == 4
        assert labels["fleet/failover/deaths"] == 1


class TestKVPayloadWire:
    def _payload(self, quantized: bool):
        rng = np.random.default_rng(0)
        k = rng.standard_normal((2, 3, 2, 8, 16)).astype(np.float32)
        v = rng.standard_normal((2, 3, 2, 8, 16)).astype(np.float32)
        return KVBlockPayload(
            uid=5, tokens=[1, 2, 3, 4], seen_tokens=4,
            last_logits=rng.standard_normal(97).astype(np.float32),
            k=k if not quantized else (k * 127).astype(np.int8),
            v=v if not quantized else (v * 127).astype(np.int8),
            k_scale=(rng.standard_normal((2, 3, 2, 8)).astype(np.float32)
                     if quantized else None),
            v_scale=(rng.standard_normal((2, 3, 2, 8)).astype(np.float32)
                     if quantized else None),
            kv_cache_dtype="int8" if quantized else "bfloat16",
            block_size=8, weight_version=3)

    @pytest.mark.parametrize("quantized", [False, True])
    def test_byte_exact_roundtrip(self, quantized):
        p = self._payload(quantized)
        from shuffle_exchange_tpu.serving.rpc import (decode_frame,
                                                      encode_frame)
        meta, planes = kv_payload_to_wire(p)
        # ship it through the REAL frame codec, not just the dict helpers
        meta2, planes2 = decode_frame(encode_frame(meta, planes))
        meta2.pop("bufs")
        back = kv_payload_from_wire(meta2, planes2)
        assert back.uid == 5 and back.tokens == [1, 2, 3, 4]
        assert back.seen_tokens == 4 and back.block_size == 8
        assert back.weight_version == 3
        assert back.kv_cache_dtype == p.kv_cache_dtype
        assert back.k.tobytes() == p.k.tobytes()
        assert back.v.tobytes() == p.v.tobytes()
        if quantized:
            assert back.k_scale.tobytes() == p.k_scale.tobytes()
            assert back.v_scale.tobytes() == p.v_scale.tobytes()
        else:
            assert back.k_scale is None and back.v_scale is None
        np.testing.assert_array_equal(back.last_logits, p.last_logits)

    def test_plane_count_mismatch_refused(self):
        p = self._payload(False)
        meta, planes = kv_payload_to_wire(p)
        with pytest.raises(ValueError):
            kv_payload_from_wire(meta, planes[:-1])


# ---------------------------------------------------------------------------
# router bookkeeping regressions (duck-typed fleet, no processes)
# ---------------------------------------------------------------------------


def _bare_fleet():
    """A ProcessReplicaRouter skeleton with just the bookkeeping the
    placement/failover/transfer paths touch — ``_call`` is substituted
    per test, so no process (or socket) ever exists."""
    from shuffle_exchange_tpu.serving.procfleet import ProcessReplicaRouter

    fleet = object.__new__(ProcessReplicaRouter)
    fleet.clock = lambda: 100.0
    fleet.requests = {}
    fleet.owner = {}
    fleet._pending = []
    fleet._maybe_resident = {}
    fleet.recovered = 0
    fleet.reprefill_tokens = 0
    fleet.migrated_sequences = 0
    fleet.migrated_blocks = 0
    fleet.workers = {}
    fleet._placement_order = lambda handles, adapter_id=None: sorted(
        handles, key=lambda h: h.replica_id)
    return fleet


def _req(uid, state="queued", generated=()):
    r = ServingRequest(uid=uid, prompt=[1, 2], max_new_tokens=8)
    r.state = state
    r.generated = list(generated)
    return r


class TestRouterBookkeepingRegressions:
    def test_place_pending_keeps_concurrent_failover_appends(self):
        """A worker dying DURING _place_pending's inject appends its
        victims to self._pending mid-loop (via _fail_over); the final
        bookkeeping must not overwrite them with a pre-loop snapshot —
        a dropped victim stays 'queued' with no owner forever."""
        fleet = _bare_fleet()
        fleet.workers = {0: SimpleNamespace(replica_id=0, state="active")}
        fleet.requests = {1: _req(1), 2: _req(2, state="running")}
        fleet.owner = {2: 9}
        fleet._pending = [1]

        def call(h, method, payload=None, bufs=(), timeout_s=None):
            # mid-inject, a different worker fails over and requeues 2
            fleet.requests[2].state = "queued"
            fleet.owner.pop(2, None)
            fleet._pending.append(2)
            return {}, []

        fleet._call = call
        assert fleet._place_pending() == 1
        assert fleet.owner[1] == 0
        assert fleet._pending == [2]   # the concurrent append survived

    def test_place_pending_timeout_marks_maybe_resident(self):
        """An inject that times out may still have been admitted by a
        slow worker — the uid must be remembered for the duplicate reap,
        and stay pending (no silent loss, no untracked copy)."""
        fleet = _bare_fleet()
        fleet.workers = {0: SimpleNamespace(replica_id=0, state="active")}
        fleet.requests = {1: _req(1)}
        fleet._pending = [1]

        def call(h, method, payload=None, bufs=(), timeout_s=None):
            raise RpcTimeout(method, 0.5)

        fleet._call = call
        assert fleet._place_pending() == 0
        assert fleet._pending == [1]
        assert 1 in fleet._maybe_resident[0]

    def test_transfer_kv_export_timeout_requeues_from_mirror(self):
        """A lost export_kv reply may have happened AFTER the source
        detached the sequence (handoff=True): the router mirror is then
        the only live copy — it must land on the pending path, never
        orphan in 'running' with a stale owner."""
        fleet = _bare_fleet()
        fleet.workers = {0: SimpleNamespace(replica_id=0, state="active"),
                         1: SimpleNamespace(replica_id=1, state="active")}
        fleet.requests = {5: _req(5, state="running", generated=[3])}
        fleet.owner = {5: 0}

        def call(h, method, payload=None, bufs=(), timeout_s=None):
            raise RpcTimeout(method, 1.0)

        fleet._call = call
        with pytest.raises(RpcTimeout):
            fleet.transfer_kv(0, 1, 5)
        assert fleet._pending == [5] and 5 not in fleet.owner
        assert fleet.requests[5].state == "queued"
        assert 5 in fleet._maybe_resident[0]   # export may never have run

    def test_transfer_kv_import_connection_lost_requeues(self):
        """The destination vanishing mid-import must requeue the uid:
        dst's own failover only reclaims dst-OWNED uids, and this one
        still maps to the source — which has already detached it."""
        fleet = _bare_fleet()
        fleet.workers = {0: SimpleNamespace(replica_id=0, state="active"),
                         1: SimpleNamespace(replica_id=1, state="active")}
        fleet.requests = {5: _req(5, state="running", generated=[3])}
        fleet.owner = {5: 0}

        def call(h, method, payload=None, bufs=(), timeout_s=None):
            if method == "export_kv":
                return {"payload": {"seen_tokens": 4, "block_size": 8},
                        "request": {"generated": [3, 4]}}, []
            raise RpcConnectionLost("peer reset")

        fleet._call = call
        with pytest.raises(RpcConnectionLost):
            fleet.transfer_kv(0, 1, 5)
        assert fleet._pending == [5] and 5 not in fleet.owner
        r = fleet.requests[5]
        assert r.state == "queued"
        assert r.generated == [3, 4]   # the export's fresher continuation

    def test_worker_cancel_reaps_known_and_ignores_unknown(self):
        """The worker half of the duplicate reap: named uids leave the
        scheduler (KV freed via fail()), unknown uids — the common case,
        where the timed-out call never landed — are silently fine."""
        from shuffle_exchange_tpu.serving.worker import ReplicaWorker

        class _Sched:
            def __init__(self):
                self.requests = {5: _req(5, state="running")}
                self.failed = []

            def fail(self, r, err, now):
                r.state = "failed"
                self.failed.append(r.uid)

        w = SimpleNamespace(_lock=threading.RLock(), scheduler=_Sched())
        out = ReplicaWorker._h_cancel(w, {"uids": [5, 9]}, [])
        assert out == {"cancelled": [5]}
        assert w.scheduler.failed == [5]
        assert 5 not in w.scheduler.requests


# ---------------------------------------------------------------------------
# real worker processes (@slow — each worker is a fresh Python + jax)
# ---------------------------------------------------------------------------


def _spec(init_seed=0, **router_kw):
    router = dict(heartbeat_interval_s=0.25, suspect_after_misses=4,
                  dead_after_misses=16, tick_timeout_s=10.0,
                  health_check_interval_s=0.05, poison_death_threshold=3,
                  fleet_mode="process", rpc_call_timeout_s=5.0,
                  rpc_ping_timeout_s=2.0, worker_start_timeout_s=180.0)
    router.update(router_kw)
    return {
        "model": dict(vocab=97, d=32, layers=2, heads=4, seq=128,
                      activation="swiglu", norm="rmsnorm", position="rope",
                      n_kv_heads=2, tie_embeddings=False),
        "init_seed": init_seed,
        "inference": dict(dtype="float32", max_seq_len=64, kv_block_size=8,
                          num_kv_blocks=40,
                          serving={"token_budget": 16, "max_running": 4,
                                   "chunk_min": 4},
                          router=router),
    }


def _prompts(n, rng=None, lo=4, hi=10):
    rng = rng or np.random.default_rng(0)
    return [rng.integers(1, 97, size=int(k)).tolist()
            for k in rng.integers(lo, hi, size=n)]


def _reference(spec, prompts, max_new):
    from shuffle_exchange_tpu.serving.chaos import _reference_tokens
    from shuffle_exchange_tpu.serving.worker import build_engine_from_spec

    return _reference_tokens(lambda: build_engine_from_spec(spec),
                             prompts, max_new)


def _drive(fleet, uids, timeout_s=180.0, revive_to=None):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        fleet.poll()
        fleet.check_health()
        fleet._place_pending()
        if revive_to and len(fleet.active_workers) < revive_to:
            fleet.scale_to(revive_to)
        if (all(fleet.requests[u].state in ("finished", "failed")
                for u in uids) and not fleet._pending):
            return
        time.sleep(0.01)
    raise TimeoutError(
        f"fleet did not drain: "
        f"{[(u, fleet.requests[u].state) for u in uids]}")


@pytest.mark.slow
class TestProcessFleet:
    def test_parity_drain_and_publish(self):
        """One fleet, three contracts: greedy parity over the socket,
        mid-flight drain-replay over RPC, and the two-phase weight flip
        actually changing what the fleet serves (seed-1 weights -> the
        seed-1 oracle's tokens)."""
        from shuffle_exchange_tpu.serving.procfleet import \
            ProcessReplicaRouter
        from shuffle_exchange_tpu.serving.worker import \
            build_engine_from_spec

        spec = _spec()
        prompts = _prompts(4)
        ref0 = _reference(spec, prompts, 6)
        fleet = ProcessReplicaRouter(spec, 2)
        try:
            # -- parity + elastic drain while requests are in flight ----
            uids = [fleet.submit(p, max_new_tokens=6) for p in prompts]
            fleet.drain(1)   # graceful: exports over RPC, requeues on 0
            _drive(fleet, uids)
            assert [fleet.requests[u].generated
                    for u in uids] == ref0
            assert fleet.drains == 1
            assert len(fleet.active_workers) == 1
            # -- two-phase publish flips the surviving worker ------------
            seed1 = _spec(init_seed=1)
            params1 = build_engine_from_spec(seed1).params
            version = fleet.publish_weights(params1)
            assert version == 1 and fleet.published_version == 1
            ref1 = _reference(seed1, prompts, 6)
            uids2 = [fleet.submit(p, max_new_tokens=6) for p in prompts]
            _drive(fleet, uids2)
            assert [fleet.requests[u].generated
                    for u in uids2] == ref1
        finally:
            fleet.stop()

    def test_chaos_drill_kill9_and_sigstop(self):
        """The acceptance drill at test scale: one real SIGKILL + one
        real SIGSTOP mid-trace; the drill itself asserts zero lost,
        parity, ACTIVE-only recovery, and deaths >= kills."""
        from shuffle_exchange_tpu.serving.chaos import \
            run_process_chaos_drill

        spec = _spec(rpc_call_timeout_s=2.0, rpc_ping_timeout_s=1.0)
        report = run_process_chaos_drill(
            spec, n_replicas=2, n_requests=6, max_new=6, span_s=2.5,
            kills=[(2, "kill", 0), (4, "stop", 1)], timeout_s=300.0)
        assert report["lost"] == 0 and report["token_mismatches"] == 0
        assert report["failover"]["deaths"] >= 2
        kinds = {k["kind"] for k in report["kills"]}
        assert kinds == {"kill", "stop"}

    def test_drain_mid_death_rolls_back_to_router_snapshots(self):
        """Satellite 6: a worker dying BETWEEN its drain export and the
        reply (the ``rpc_drain_reply`` fault, armed through SXT_FAULTS in
        the worker's environment — satellite 1) must not lose a request:
        the router never received the export, so it recovers every
        victim from its OWN snapshots through the failover path."""
        from shuffle_exchange_tpu.serving.procfleet import \
            ProcessReplicaRouter

        spec = _spec(rpc_call_timeout_s=5.0)
        prompts = _prompts(4)
        ref = _reference(spec, prompts, 6)
        fleet = ProcessReplicaRouter(
            spec, 2,
            worker_env={0: {"SXT_FAULTS": "rpc_drain_reply:index=0"}})
        try:
            uids = [fleet.submit(p, max_new_tokens=6) for p in prompts]
            assert any(fleet.owner[u] == 0 for u in uids), \
                "placement put nothing on worker 0 — test is vacuous"
            fleet.drain(0)   # dies between export and ack
            # the armed death really fired (os._exit(17)), and the drain
            # degraded to a failover instead of erroring
            assert fleet.workers[0].proc.returncode == 17
            assert fleet.drains == 0
            assert fleet.stats()["failover"]["deaths"] == 1
            _drive(fleet, uids)
            assert [fleet.requests[u].generated for u in uids] == ref
        finally:
            fleet.stop()

    def test_transfer_kv_moves_live_sequence_byte_exact(self):
        """The disagg prefill->decode handoff over the socket: a RUNNING
        sequence's KV planes cross byte-exactly (wrong bytes would
        diverge the continuation from the greedy oracle immediately)."""
        from shuffle_exchange_tpu.serving.procfleet import \
            ProcessReplicaRouter
        from shuffle_exchange_tpu.serving.rpc import RpcRemoteError

        spec = _spec()
        max_new = 40   # long decode: plenty of mid-flight window
        prompts = _prompts(3, lo=6, hi=9)
        ref = _reference(spec, prompts, max_new)
        fleet = ProcessReplicaRouter(spec, 2)
        try:
            uids = [fleet.submit(p, max_new_tokens=max_new)
                    for p in prompts]
            moved = None
            deadline = time.monotonic() + 120.0
            while moved is None and time.monotonic() < deadline:
                fleet.poll()
                for u in uids:
                    r = fleet.requests[u]
                    if r.state == "running" and len(r.generated) >= 2:
                        src = fleet.owner[u]
                        dst = next(h.replica_id
                                   for h in fleet.active_workers
                                   if h.replica_id != src)
                        try:
                            fleet.transfer_kv(src, dst, u)
                        except RpcRemoteError:
                            continue   # finished under us — try another
                        moved = u
                        break
                time.sleep(0.01)
            assert moved is not None, "no request stayed mid-decode"
            _drive(fleet, uids)
            assert [fleet.requests[u].generated for u in uids] == ref
            st = fleet.stats()
            assert st["failover"]["migrated_sequences"] >= 1
            assert st["failover"]["deaths"] == 0
        finally:
            fleet.stop()
