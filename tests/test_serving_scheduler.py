"""Continuous-batching serving scheduler (ISSUE 5): Dynamic-SplitFuse
ticks must be (a) exact-token-identical to the sequential put()+decode_loop
reference, (b) ONE dispatch per tick, (c) compile-bounded by the shape-bin
ladder, (d) starvation-free for running decodes, and (e) correct through
KV-exhaustion preemption/requeue.
"""

import numpy as np
import pytest

import jax

from shuffle_exchange_tpu.config import ConfigError
from shuffle_exchange_tpu.inference import (ContinuousBatchingScheduler,
                                            InferenceConfig,
                                            InferenceEngineV2, ServingConfig)
from shuffle_exchange_tpu.models import Transformer, tiny


@pytest.fixture(scope="module")
def model_and_params():
    cfg = tiny(vocab=97, d=32, layers=2, heads=4, seq=128,
               activation="swiglu", norm="rmsnorm", position="rope",
               n_kv_heads=2, tie_embeddings=False)
    model = Transformer(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _icfg(num_kv_blocks=40, **serving):
    serving = {"token_budget": 16, "max_running": 4, "chunk_min": 4,
               **serving}
    return InferenceConfig(dtype="float32", max_seq_len=64, kv_block_size=8,
                           num_kv_blocks=num_kv_blocks, serving=serving)


def _reference(model, params, prompt, n_new):
    """The sequential serving reference: one put() prefill, then the fused
    decode_loop — the engine-parity oracle the scheduler must reproduce."""
    eng = InferenceEngineV2(model, params, _icfg())
    lg = eng.put([0], [prompt])
    first = int(np.argmax(lg[0]))
    if n_new == 1:
        return [first]
    toks = eng.decode_loop([0], [first], n_new - 1)
    return [first] + [int(t) for t in toks[0]]


class TestParity:
    def test_scheduled_serving_matches_sequential_reference(self, model_and_params):
        """Mixed prefill+decode ticks produce EXACTLY the tokens the
        sequential put()+decode_loop path does, for every request, under
        concurrent admission."""
        model, params = model_and_params
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, 90, size=int(n)).tolist()
                   for n in (12, 5, 22, 9)]
        want = [_reference(model, params, p, 8) for p in prompts]
        eng = InferenceEngineV2(model, params, _icfg())
        sched = ContinuousBatchingScheduler(eng)
        out = sched.serve(prompts, max_new_tokens=8)
        assert [out[u] for u in out] == want
        # every admitted sequence was flushed on finish: pool fully free
        assert eng.free_blocks == eng.allocator.num_blocks - 1

    def test_one_dispatch_per_tick(self, model_and_params):
        """The whole mixed batch of a tick — decodes AND prefill chunks —
        is ONE compiled dispatch (the tentpole contract)."""
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg())
        sched = ContinuousBatchingScheduler(eng)
        rng = np.random.default_rng(1)
        for n in (10, 18, 7):
            sched.submit(rng.integers(1, 90, size=n).tolist(), max_new_tokens=6)
        d0 = eng.dispatch_count
        while sched.tick():
            pass
        assert eng.dispatch_count - d0 == sched.ticks
        # and ticks actually mixed phases at least once
        assert any(k[0] == "mixed" for k in eng.program_shapes)

    def test_preemption_requeue_identical_output(self, model_and_params):
        """6 usable blocks x 8 slots < the two requests' total KV: the
        youngest sequence is preempted, requeued, replayed — and every
        request's tokens still match the uninterrupted reference."""
        model, params = model_and_params
        rng = np.random.default_rng(1)
        prompts = [rng.integers(1, 90, size=20).tolist(),
                   rng.integers(1, 90, size=18).tolist()]
        want = [_reference(model, params, p, 12) for p in prompts]
        eng = InferenceEngineV2(model, params, _icfg(num_kv_blocks=7))
        sched = ContinuousBatchingScheduler(eng)
        out = sched.serve(prompts, max_new_tokens=12)
        assert sched.preemptions > 0, "pool was sized to force preemption"
        assert [out[u] for u in out] == want
        assert sched.memory_monitor.latest("serving/preemptions") == sched.preemptions

    def test_streaming_tokens_arrive_in_order(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg())
        streamed = []
        sched = ContinuousBatchingScheduler(
            eng, on_token=lambda uid, tok: streamed.append((uid, tok)))
        rng = np.random.default_rng(2)
        prompts = [rng.integers(1, 90, size=6).tolist(),
                   rng.integers(1, 90, size=11).tolist()]
        out = sched.serve(prompts, max_new_tokens=5)
        for uid, toks in out.items():
            assert [t for u, t in streamed if u == uid] == toks


class TestScheduling:
    def test_compile_count_bounded_by_shape_bin_ladder(self, model_and_params):
        """A long, varied workload compiles a bounded program set (shapes
        only from the bin ladder), and a SECOND identical workload on the
        warmed engine compiles nothing new — the production property that
        a warmed server never recompiles."""
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg())
        cfg = eng.config.serving
        rng = np.random.default_rng(3)

        def workload():
            sched = ContinuousBatchingScheduler(eng)
            rq = np.random.default_rng(7)
            prompts = [rq.integers(1, 90, size=int(n)).tolist()
                       for n in rq.integers(3, 30, size=10)]
            news = [int(n) for n in rq.integers(2, 9, size=10)]
            sched.serve(list(zip(prompts, news)))
            return sched

        sched = workload()
        shapes = eng.program_shapes
        assert sched.ticks > len(shapes), (sched.ticks, shapes)
        # every shape comes off the ladder: powers of two for batch/width,
        # serving chunk bins for C
        def pow2(n):
            return n & (n - 1) == 0
        for key in shapes:
            if key[0] == "mixed":
                _, bd, wd, bp, c, wp = key
                assert all(map(pow2, (bd, wd, bp, wp))), key
                assert c == cfg.bin_chunk(c), key
            elif key[0] == "decode":
                assert all(map(pow2, key[1:])), key
            elif key[0] == "extend":
                _, bp, c, wp = key
                assert pow2(bp) and pow2(wp) and c == cfg.bin_chunk(c), key
        assert len(shapes) <= 20, sorted(shapes)
        # warmed server: the same trace again adds zero program shapes
        workload()
        assert eng.program_shapes == shapes

    def test_long_prefill_cannot_stall_running_decodes(self, model_and_params):
        """Starvation bound: while a long prompt chews through chunked
        prefill, every running sequence still advances one token per tick,
        and no chunk exceeds budget - running."""
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg(
            num_kv_blocks=40, token_budget=8, max_running=4, chunk_min=2))
        sched = ContinuousBatchingScheduler(eng)
        rng = np.random.default_rng(4)
        a = sched.submit(rng.integers(1, 90, size=5).tolist(), max_new_tokens=30)
        b = sched.submit(rng.integers(1, 90, size=6).tolist(), max_new_tokens=30)
        while not all(sched.requests[u].state == "running" for u in (a, b)):
            sched.tick()
        long_uid = sched.submit(rng.integers(1, 90, size=40).tolist(),
                                max_new_tokens=2)
        long_req = sched.requests[long_uid]
        prefill_ticks = 0
        while long_req.state in ("queued", "prefill"):
            ga, gb = (len(sched.requests[u].generated) for u in (a, b))
            done_before = long_req.prefill_done
            sched.tick()
            prefill_ticks += 1
            # running decodes advanced this tick despite the long prefill
            assert len(sched.requests[a].generated) == ga + 1
            assert len(sched.requests[b].generated) == gb + 1
            # the chunk obeyed the budget with both decodes packed
            assert long_req.prefill_done - done_before <= 8 - 2
        assert prefill_ticks >= 40 // 6, "prompt should take several chunks"
        sched.drain()
        assert sched.requests[long_uid].state == "finished"

    def test_serving_counters_through_memory_monitor(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg())
        sched = ContinuousBatchingScheduler(eng)
        rng = np.random.default_rng(5)
        sched.serve([rng.integers(1, 90, size=9).tolist() for _ in range(3)],
                    max_new_tokens=4)
        mm = sched.memory_monitor
        assert len(mm.values("serving/ttft_s")) == 3
        assert len(mm.values("serving/tpot_s")) == 3 * 3   # max_new-1 per req
        assert mm.values("serving/budget_fill")
        assert all(0 < f <= 1 for f in mm.values("serving/budget_fill"))
        assert mm.latest("serving/queue_depth") == 0
        st = sched.stats()
        assert st["requests"] == 3 and st["generated_tokens"] == 12
        assert st["ttft_p50_s"] > 0 and st["tpot_p50_s"] > 0

    def test_arrival_trace_defers_submission(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg())
        sched = ContinuousBatchingScheduler(eng)
        rng = np.random.default_rng(6)
        prompts = [rng.integers(1, 90, size=5).tolist() for _ in range(3)]
        out = sched.serve(prompts, max_new_tokens=3,
                          arrivals=[0.0, 0.0, 0.05])
        assert len(out) == 3
        assert all(len(t) == 3 for t in out.values())
        # the late arrival was submitted measurably after the first two
        subs = sorted(r.submitted_at for r in sched.requests.values())
        assert subs[2] - subs[0] >= 0.04


class TestAdmissionErrors:
    def test_put_kv_exhaustion_names_numbers(self, model_and_params):
        """ISSUE 5 satellite: put() admission failures name needed vs free
        KV blocks and the offending uid, like decode_loop's do."""
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg(num_kv_blocks=5))
        with pytest.raises(RuntimeError,
                           match=r"needs \d+ KV blocks, \d+ free.*uid 7"):
            eng.put([7], [list(range(1, 50))])

    def test_put_seq_len_overrun_names_uid(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg())
        with pytest.raises(RuntimeError, match=r"uid 3 would overrun "
                                               r"max_seq_len: 0 seen \+ 70"):
            eng.put([3], [list(range(70))])

    def test_step_rejects_dual_role_uid(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg())
        eng.put([1], [[5, 6, 7]])
        with pytest.raises(ValueError,
                           match="either decoding, prefilling or verifying"):
            eng.step([1], [9], [(1, [4, 4])])
        with pytest.raises(ValueError, match="decode uid 42 unknown"):
            eng.step([42], [1], [])

    def test_step_leaves_state_untouched_on_rejection(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg(num_kv_blocks=6))
        eng.put([1], [[5, 6, 7]])
        free0, seen0 = eng.free_blocks, eng._seqs[1].seen_tokens
        with pytest.raises(RuntimeError, match="KV blocks"):
            eng.step([1], [9], [(2, list(range(1, 40)))])
        assert eng.free_blocks == free0
        assert eng._seqs[1].seen_tokens == seen0
        assert 2 not in eng._seqs

    def test_submit_validation_names_limits(self, model_and_params):
        model, params = model_and_params
        eng = InferenceEngineV2(model, params, _icfg(num_kv_blocks=4))
        sched = ContinuousBatchingScheduler(eng)
        with pytest.raises(ValueError, match="exceeds max_seq_len"):
            sched.submit(list(range(60)), max_new_tokens=10)
        with pytest.raises(ValueError, match="KV blocks but the pool has"):
            sched.submit(list(range(30)), max_new_tokens=10)


class TestServingConfig:
    def test_ladder_and_validation(self):
        sv = ServingConfig(token_budget=64, chunk_min=8)
        assert sv.bins() == (8, 16, 32, 64)
        assert sv.bin_chunk(1) == 8 and sv.bin_chunk(20) == 32
        assert sv.bin_chunk(65) == 128   # direct step() callers stay binned
        with pytest.raises(ConfigError, match="max_running"):
            ServingConfig(token_budget=4, max_running=8)
        with pytest.raises(ConfigError, match="chunk_min"):
            ServingConfig(token_budget=4, max_running=2, chunk_min=8)

    def test_from_dict_rejects_unknown_serving_keys(self):
        with pytest.raises(ConfigError, match="unknown serving config keys"):
            InferenceConfig.from_dict({"serving": {"token_bugdet": 64}})
        cfg = InferenceConfig.from_dict(
            {"serving": {"token_budget": 128, "chunk_bins": [32, 64]}})
        assert cfg.serving.token_budget == 128
        assert cfg.serving.bins() == (32, 64)
